"""Host-pipeline benchmarks (BASELINE.md config #3 end-to-end and the
cached-state-root criterion from VERDICT r1 #9).

1. Gossip pipeline END-TO-END (config #3): N single-bit REAL-signed
   attestations submitted to the BeaconProcessor, coalesced into
   device-bucket batches, signature-verified on the ``cpu-native`` C
   backend, applied to fork choice. Reports attestations/sec and the
   p50/p99 submit-to-verified latency (queue wait + verify together) —
   the reference's measurement shape is ``attestation_verification/
   batch.rs:139-222`` feeding ``beacon_processor/mod.rs:1008-1099``.
2. Gossip pipeline HOST-ONLY: same run on the ``fake`` backend, isolating
   scheduler/structural cost (the device cost is bench.py's job).
3. State re-hash: full hash_tree_root vs the incremental cached root on a
   large registry after a per-slot-shaped mutation.

Run: python benches/bench_pipeline.py [n_attestations] [n_validators]
"""

from __future__ import annotations

import copy
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mk_bench_chain(n_validators: int):
    from lighthouse_tpu.beacon_chain import BeaconChain
    from lighthouse_tpu.state_transition import store_replayer
    from lighthouse_tpu.store import HotColdDB, MemoryStore
    from lighthouse_tpu.testing.harness import StateHarness
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.preset import MINIMAL
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    h = StateHarness(
        MINIMAL, minimal_spec(), validator_count=n_validators,
        fork_name="phase0", fake_sign=True,
    )
    genesis = copy.deepcopy(h.state)
    db = HotColdDB(MemoryStore(), h.t, h.spec, store_replayer(h.preset, h.spec))
    clock = ManualSlotClock(genesis.genesis_time, h.spec.seconds_per_slot)
    chain = BeaconChain(h.preset, h.spec, h.t, db, genesis, slot_clock=clock)
    slot = 1
    clock.set_slot(slot)
    sb = h.produce_block(slot)
    h.process_block(sb, strategy="none")
    chain.process_block(chain.verify_block_for_gossip(sb))
    return h, chain, clock


def _real_signed_singles(h, chain, n_atts: int):
    """Single-bit attestations with REAL signatures across as many slots
    as needed, signed through the C library (native_sign)."""
    from lighthouse_tpu.crypto.native import native_sign
    from lighthouse_tpu.state_transition import (
        CommitteeCache,
        partial_state_advance,
    )
    from lighthouse_tpu.state_transition.helpers import compute_epoch_at_slot
    from lighthouse_tpu.types.chain_spec import DOMAIN_BEACON_ATTESTER
    from lighthouse_tpu.types.domains import compute_signing_root, get_domain

    t = h.t
    spe = h.preset.SLOTS_PER_EPOCH
    head_root = chain.head_block_root
    genesis_root = chain.genesis_block_root
    epoch_caches = {}
    singles = []
    slot = 1
    base = chain.head_state
    while len(singles) < n_atts:
        epoch = compute_epoch_at_slot(h.preset, slot)
        if epoch not in epoch_caches:
            st = copy.deepcopy(base)
            if st.slot < epoch * spe:
                st = partial_state_advance(h.preset, h.spec, st, epoch * spe)
            epoch_caches[epoch] = (CommitteeCache(h.preset, st, epoch), st)
        cache, st = epoch_caches[epoch]
        # target: the newest block at/before the epoch boundary
        target_root = genesis_root if epoch == 0 else head_root
        domain = get_domain(h.spec, st, DOMAIN_BEACON_ATTESTER, epoch)
        for index in range(cache.committees_per_slot):
            committee = cache.committee(slot, index)
            data = t.AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head_root,
                source=st.current_justified_checkpoint,
                target=t.Checkpoint(epoch=epoch, root=target_root),
            )
            root = compute_signing_root(t.AttestationData, data, domain)
            for pos, v in enumerate(committee):
                sig = native_sign(h.keys[int(v)].k, root)
                singles.append(
                    t.Attestation(
                        aggregation_bits=[
                            p == pos for p in range(len(committee))
                        ],
                        data=data,
                        signature=sig,
                    )
                )
                if len(singles) >= n_atts:
                    return singles, slot
            if len(singles) >= n_atts:
                return singles, slot
        slot += 1
    return singles, slot


def _fake_singles(h, n_atts: int, slot: int = 1):
    """Template duplication (host-only mode: signatures are not checked)."""
    templates = h.attestations_for_slot(h.state, slot)
    singles = []
    while len(singles) < n_atts:
        for att in templates:
            bits = list(att.aggregation_bits)
            for i in range(len(bits)):
                single = copy.deepcopy(att)
                single.aggregation_bits = [j == i for j in range(len(bits))]
                singles.append(single)
                if len(singles) >= n_atts:
                    break
            if len(singles) >= n_atts:
                break
    return singles


def bench_gossip_pipeline(n_atts: int, real: bool = False) -> dict:
    from lighthouse_tpu.beacon_chain import VerifiedUnaggregatedAttestation
    from lighthouse_tpu.beacon_processor import BeaconProcessor, Work, WorkKind
    from lighthouse_tpu.crypto import backend
    from lighthouse_tpu.utils import metrics

    # Setup (block import with the harness's stamped signature) runs on
    # the fake backend; the MEASURED attestation path switches to the
    # real one below.
    backend.set_backend("fake")
    try:
        n_validators = max(64, min(4096, n_atts)) if real else 64
        h, chain, clock = _mk_bench_chain(n_validators)
        if real:
            singles, max_slot = _real_signed_singles(h, chain, n_atts)
            clock.set_slot(max_slot + 1)
            backend.set_backend("cpu-native")
        else:
            singles = _fake_singles(h, n_atts)
            clock.set_slot(2)

        done = []
        latencies = []

        def on_batch(items):
            res = chain.batch_verify_unaggregated_attestations_for_gossip(items)
            for r in res:
                if isinstance(r, VerifiedUnaggregatedAttestation):
                    chain.apply_attestation_to_fork_choice(r)
            return res

        bp = BeaconProcessor({WorkKind.GOSSIP_ATTESTATION: on_batch}, n_workers=2)
        t0 = time.perf_counter()
        accepted = 0
        shed = 0
        for s in singles:
            w = Work(WorkKind.GOSSIP_ATTESTATION, s)
            sub = time.perf_counter()

            def record(res, _sub=sub):
                # submit-to-verified latency: queue wait + batch verify
                latencies.append(time.perf_counter() - _sub)
                done.append(res)

            w.done = record
            if bp.submit(w):
                accepted += 1
            else:
                shed += 1  # bounded-queue shedding: callbacks never fire
        while len(done) < accepted and time.perf_counter() - t0 < 300:
            time.sleep(0.005)
        dt = time.perf_counter() - t0
        bp.shutdown()

        n_verified = sum(
            1 for r in done if isinstance(r, VerifiedUnaggregatedAttestation)
        )
        lat = sorted(latencies)

        def pct(p):
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 4) if lat else None

        batch = metrics.histogram("beacon_processor_batch_size")
        return {
            "backend": backend.active_name(),
            "n_submitted": len(singles),
            "n_done": len(done),
            "n_verified": n_verified,
            "shed": shed,
            "throughput_per_sec": round(len(done) / dt, 1),
            "latency_p50_s": pct(0.50),
            "latency_p99_s": pct(0.99),
            "mean_batch": round(batch.sum / max(1, batch.total), 1),
        }
    finally:
        backend.set_backend("cpu")


def bench_state_rehash(n_validators: int) -> dict:
    from lighthouse_tpu.ssz import hash_tree_root
    from lighthouse_tpu.ssz.cache import CachedRootComputer
    from lighthouse_tpu.types.containers import types_for
    from lighthouse_tpu.types.preset import MAINNET

    t = types_for(MAINNET)
    state = t.state["phase0"]()
    v0 = t.Validator(pubkey=b"\xaa" * 48, effective_balance=32 * 10**9)
    state.validators = [copy.copy(v0) for _ in range(n_validators)]
    state.balances = [32 * 10**9] * n_validators
    for i, v in enumerate(state.validators):
        v.withdrawal_credentials = i.to_bytes(32, "little")

    comp = CachedRootComputer()
    t0 = time.perf_counter()
    r_full = hash_tree_root(state)
    t_full = time.perf_counter() - t0
    comp.hash_tree_root(state)  # warm the cache
    # per-slot-shaped mutation: a few balances + one validator + slot
    state.balances[7] += 1
    state.balances[1234 % n_validators] += 1
    state.validators[42 % n_validators].effective_balance += 1
    state.slot += 1
    t0 = time.perf_counter()
    r_inc = comp.hash_tree_root(state)
    t_inc = time.perf_counter() - t0
    assert r_inc == hash_tree_root(state)
    return {
        "n_validators": n_validators,
        "full_s": round(t_full, 3),
        "incremental_s": round(t_inc, 4),
        "speedup": round(t_full / t_inc, 1),
    }


def bench_attestation_production(n_validators: int = 2_000) -> dict:
    """Attestation-production latency across an epoch boundary: the
    production caches (early-attester template / attester cache /
    pre-advanced state) vs the cold path (full state copy + epoch
    advance) — the latency the reference buys with
    ``early_attester_cache.rs`` + ``state_advance_timer.rs``."""
    from lighthouse_tpu.beacon_chain import BeaconChain
    from lighthouse_tpu.crypto import backend
    from lighthouse_tpu.state_transition import store_replayer
    from lighthouse_tpu.store import HotColdDB, MemoryStore
    from lighthouse_tpu.testing import StateHarness
    from lighthouse_tpu.types import MINIMAL, minimal_spec
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    backend.set_backend("fake")
    try:
        h = StateHarness(
            MINIMAL, minimal_spec(), validator_count=n_validators,
            fork_name="phase0", fake_sign=True,
        )
        genesis = copy.deepcopy(h.state)
        db = HotColdDB(
            MemoryStore(), h.t, h.spec, store_replayer(h.preset, h.spec),
            slots_per_snapshot=8,
        )
        clock = ManualSlotClock(genesis.genesis_time, h.spec.seconds_per_slot)
        chain = BeaconChain(h.preset, h.spec, h.t, db, genesis, slot_clock=clock)
        for _ in range(2):
            slot = h.state.slot + 1
            clock.set_slot(slot)
            sb = h.produce_block(slot)
            h.process_block(sb, strategy="none")
            chain.process_block(chain.verify_block_for_gossip(sb))

        boundary_slot = MINIMAL.SLOTS_PER_EPOCH + 1
        clock.set_slot(boundary_slot)

        def timed(f):
            t0 = time.perf_counter()
            out = f()
            return out, time.perf_counter() - t0

        # cold: no caches — full copy + epoch advance
        chain.early_attester_cache._item = None
        chain.attester_cache._map.clear()
        chain._advanced = None
        a_cold, t_cold = timed(
            lambda: chain.produce_unaggregated_attestation(boundary_slot, 0)
        )
        # warm: attester cache filled by the cold call
        a_warm, t_warm = timed(
            lambda: chain.produce_unaggregated_attestation(boundary_slot, 0)
        )
        assert a_cold == a_warm
        # pre-advanced (state-advance timer ran, caches cleared)
        chain.attester_cache._map.clear()
        chain.advance_head_state_to(boundary_slot)
        a_adv, t_adv = timed(
            lambda: chain.produce_unaggregated_attestation(boundary_slot, 0)
        )
        assert a_adv == a_cold
        return {
            "n_validators": n_validators,
            "cold_ms": round(t_cold * 1e3, 2),
            "attester_cache_ms": round(t_warm * 1e3, 3),
            "pre_advanced_ms": round(t_adv * 1e3, 3),
            "speedup_cache": round(t_cold / max(t_warm, 1e-9), 1),
            "speedup_pre_advanced": round(t_cold / max(t_adv, 1e-9), 1),
        }
    finally:
        backend.set_backend("cpu")


if __name__ == "__main__":
    n_atts = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    n_vals = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    out = {
        "gossip_pipeline_e2e": bench_gossip_pipeline(n_atts, real=True),
        "gossip_pipeline_host_only": bench_gossip_pipeline(n_atts),
        "state_rehash": bench_state_rehash(n_vals),
        "attestation_production": bench_attestation_production(),
    }
    print(json.dumps(out, indent=2))
